"""Benchmark: flagship train-step throughput on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference (Yun-960/Pytorch-Distributed-Template) publishes no benchmark
numbers (SURVEY.md §6), so the baseline is *measured here*: the reference's
own MNIST workload (LeNet, the architecture of
/root/reference/model/model.py:6-22) run with torch on this host's CPU —
the reference's only in-tree runnable config. ``vs_baseline`` is our
TPU-native throughput over that measured reference throughput.
"""
from __future__ import annotations

import json
import time

import numpy as np

BATCH = 512
WARMUP = 5
STEPS = 30


def bench_tpu_native() -> float:
    import jax
    import optax

    from pytorch_distributed_template_tpu.config.registry import (
        LOSSES, METRICS, MODELS,
    )
    from pytorch_distributed_template_tpu.engine.state import create_train_state
    from pytorch_distributed_template_tpu.engine.steps import make_train_step
    from pytorch_distributed_template_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_template_tpu.parallel.sharding import (
        apply_rules, batch_sharding,
    )

    mesh = build_mesh({"data": -1}, jax.devices())
    model = MODELS.get("LeNet")(num_classes=10)
    tx = optax.adam(1e-3)
    state = create_train_state(model, tx, model.batch_template(1), seed=0)
    state = jax.device_put(state, apply_rules(state, mesh, []))

    step = jax.jit(
        make_train_step(model, tx, LOSSES.get("nll_loss"),
                        [METRICS.get("accuracy")]),
        donate_argnums=0,
    )
    rng = np.random.default_rng(0)
    bs = batch_sharding(mesh)
    batch = {
        "image": jax.device_put(
            rng.normal(size=(BATCH, 28, 28, 1)).astype(np.float32), bs),
        "label": jax.device_put(
            rng.integers(0, 10, size=BATCH).astype(np.int32), bs),
        "mask": jax.device_put(np.ones(BATCH, bool), bs),
    }
    for _ in range(WARMUP):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    dt = time.perf_counter() - t0
    return BATCH * STEPS / dt


def bench_reference_torch() -> float:
    """The reference's MNIST workload, measured with torch on this host.

    Architecture per /root/reference/model/model.py:6-22 (written here
    independently from the SURVEY description: conv10-5x5 / pool / relu /
    conv20-5x5 / dropout / pool / relu / fc50 / fc10 / log_softmax).
    """
    import torch
    import torch.nn.functional as F
    from torch import nn

    torch.manual_seed(0)

    class RefNet(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(1, 10, 5)
            self.c2 = nn.Conv2d(10, 20, 5)
            self.drop = nn.Dropout2d()
            self.f1 = nn.Linear(320, 50)
            self.f2 = nn.Linear(50, 10)

        def forward(self, x):
            x = F.relu(F.max_pool2d(self.c1(x), 2))
            x = F.relu(F.max_pool2d(self.drop(self.c2(x)), 2))
            x = x.flatten(1)
            x = F.dropout(F.relu(self.f1(x)), training=self.training)
            return F.log_softmax(self.f2(x), dim=1)

    model = RefNet().train()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    x = torch.randn(BATCH, 1, 28, 28)
    y = torch.randint(0, 10, (BATCH,))
    n_steps = 8
    for _ in range(2):
        opt.zero_grad(); F.nll_loss(model(x), y).backward(); opt.step()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        opt.zero_grad(); F.nll_loss(model(x), y).backward(); opt.step()
    dt = time.perf_counter() - t0
    return BATCH * n_steps / dt


def main():
    ours = bench_tpu_native()
    try:
        ref = bench_reference_torch()
    except Exception:
        ref = float("nan")
    vs = ours / ref if ref == ref and ref > 0 else 0.0
    print(json.dumps({
        "metric": "mnist_lenet_train_images_per_sec",
        "value": round(ours, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
